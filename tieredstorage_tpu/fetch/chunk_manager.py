"""Chunk managers: resolve (object key, manifest, chunk id) -> plaintext chunk.

Reference: core/.../fetch/ChunkManager.java:25-29 and
DefaultChunkManager.java:50-66 (ranged fetch of the transformed chunk, then
decrypt/decompress). Extended here with a batch entry point — `get_chunks`
fetches a window of chunks with ONE ranged request (chunks are contiguous on
the stored side) and detransforms them in ONE backend call, which is the unit
of work the TPU backend wants and what cache prefetch windows use.
"""

from __future__ import annotations

import abc
import io
import logging
import time
from typing import BinaryIO, Callable, Optional, Sequence

from tieredstorage_tpu.manifest.segment_manifest import SegmentManifestV1
from tieredstorage_tpu.storage.core import (
    BytesRange,
    ObjectFetcher,
    ObjectKey,
    StorageBackendException,
)
from tieredstorage_tpu.utils import faults, flightrecorder as flight
from tieredstorage_tpu.utils.locks import new_lock
from tieredstorage_tpu.transform.api import DetransformOptions, TransformBackend
from tieredstorage_tpu.utils.deadline import check_deadline
from tieredstorage_tpu.utils.streams import read_exactly
from tieredstorage_tpu.utils.tracing import NOOP_TRACER

log = logging.getLogger(__name__)


class CorruptChunkException(StorageBackendException):
    """Detransform failed on fetched bytes (GCM tag / CRC / frame mismatch):
    the stored object is corrupt or forged. The object key is quarantined so
    broker retry storms can't hammer a poisoned object."""


class ChunkManager(abc.ABC):
    @abc.abstractmethod
    def get_chunk(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, chunk_id: int
    ) -> BinaryIO:
        """Plaintext stream of one original-side chunk."""

    def get_chunks(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, chunk_ids: Sequence[int]
    ) -> list[bytes]:
        """Plaintext bytes of several chunks; default loops over get_chunk."""
        return [
            self.get_chunk(objects_key, manifest, cid).read() for cid in chunk_ids
        ]


class DefaultChunkManager(ChunkManager):
    #: How long a key stays quarantined after a detransform failure.
    DEFAULT_QUARANTINE_TTL_S = 60.0

    #: Span recorder; the RSM swaps in its configured tracer so the storage
    #: GET and detransform stages land in the request's trace tree.
    tracer = NOOP_TRACER
    #: Optional latency hook `(elapsed_ms, plaintext_bytes)` per batch; the
    #: RSM wires it to Metrics.record_chunk_fetch.
    on_fetch: Optional[Callable[[float, int], None]] = None
    #: Optional tail-tolerance hedger (fetch/hedge.py); when set, the ranged
    #: storage GET of a chunk window is raced against a delayed second
    #: attempt and the first success wins (`hedge.enabled`).
    hedger = None
    #: Optional pre-detransform hook `(opts)` — the device hot-window tier
    #: (fetch/cache/device_hot.py `note_detransform`) records the window's
    #: DetransformOptions so admission can tell whether the decrypt output
    #: rows ARE the final plaintext (encryption-only segments) and the
    #: device buffer may be retained for hot serving.
    on_detransform = None

    def __init__(
        self,
        fetcher: ObjectFetcher,
        transform_backend: TransformBackend,
        *,
        quarantine_ttl_s: Optional[float] = None,
        time_source: Callable[[], float] = time.monotonic,
    ):
        self._fetcher = fetcher
        self._backend = transform_backend
        self.quarantine_ttl_s = (
            self.DEFAULT_QUARANTINE_TTL_S if quarantine_ttl_s is None else quarantine_ttl_s
        )
        self._now = time_source
        self._quarantine: dict[str, tuple[float, str]] = {}
        self._quarantine_lock = new_lock("chunk_manager.DefaultChunkManager._quarantine_lock")
        #: Total detransform corruption detections (exported as a gauge).
        self.corruptions = 0

    @property
    def quarantined_keys(self) -> int:
        with self._quarantine_lock:
            return len(self._quarantine)

    def _check_quarantine(self, key: ObjectKey) -> None:
        with self._quarantine_lock:
            entry = self._quarantine.get(key.value)
            if entry is None:
                return
            expires_at, reason = entry
            if self._now() >= expires_at:
                del self._quarantine[key.value]
                return
        raise CorruptChunkException(
            f"Object {key} is quarantined after a detransform failure: {reason}"
        )

    def _quarantine_key(self, key: ObjectKey, reason: str) -> None:
        with self._quarantine_lock:
            self.corruptions += 1
            self._quarantine[key.value] = (self._now() + self.quarantine_ttl_s, reason)
        self.tracer.event("chunk.quarantine", key=key.value, reason=reason)
        log.warning("Quarantining %s for %.0fs: %s", key, self.quarantine_ttl_s, reason)

    def quarantine(self, key: ObjectKey, reason: str) -> None:
        """External quarantine hook: the scrubber routes objects it finds
        corrupt at rest through the same gate a detransform failure takes,
        so fetches fail fast instead of re-reading poisoned bytes."""
        self._quarantine_key(key, reason)

    def get_chunk(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, chunk_id: int
    ) -> BinaryIO:
        return io.BytesIO(self.get_chunks(objects_key, manifest, [chunk_id])[0])

    def get_chunks(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, chunk_ids: Sequence[int]
    ) -> list[bytes]:
        if len(chunk_ids) == 0:
            return []
        self._check_quarantine(objects_key)
        # Fast-fail BEFORE the ranged GET: a request whose end-to-end
        # deadline already expired must not spend a storage round trip.
        check_deadline(f"chunk fetch of {objects_key}")
        start = time.monotonic()
        index = manifest.chunk_index
        chunks = [index._chunk_at(cid) for cid in chunk_ids]
        contiguous = all(
            chunks[i + 1].id == chunks[i].id + 1 for i in range(len(chunks) - 1)
        )
        with self.tracer.span(
            "storage.fetch_chunks", key=objects_key.value, chunks=len(chunks),
        ) as fetch_span:
            if self.hedger is not None:
                stored = self.hedger.call(
                    lambda: self._fetch_stored(objects_key, chunks, contiguous),
                    what=objects_key.value,
                    hedge_fn=self._hedge_attempt(objects_key, chunks, contiguous),
                )
            else:
                stored = self._fetch_stored(objects_key, chunks, contiguous)
            stored_bytes = sum(len(b) for b in stored)
            if fetch_span is not None:
                fetch_span.attributes["bytes"] = stored_bytes
        # Flight-record the backend serve: this window's chunks came from
        # remote storage (every tier above missed), with the deadline budget
        # left after the ranged GET.
        flight.note("tier.backend", len(chunk_ids))
        flight.stage(f"backend.fetched:{objects_key.value.rsplit('/', 1)[-1]}")
        opts = DetransformOptions.from_manifest(manifest)
        if self.on_detransform is not None:
            self.on_detransform(opts)
        # GCM window accounting for the record: the TPU backend exposes its
        # per-thread dispatch/HBM-round-trip counters (CPU backends don't —
        # duck-typed, zero coupling).
        thread_counters = getattr(self._backend, "thread_dispatch_counters", None)
        counters_before = thread_counters() if thread_counters is not None else None
        # Batch-evidence seam (ISSUE 15): with cross-request batching on,
        # this request's launches ride the flusher thread — the per-thread
        # dispatch counters above stay 0 by design, and the batcher's own
        # evidence (coalesced windows, occupancy, shared batch id) is what
        # proves which launch the request shared.
        batch_seam = getattr(self._backend, "thread_batch_evidence", None)
        batch_before = batch_seam() if batch_seam is not None else None
        try:
            with self.tracer.span(
                "chunk.detransform", chunks=len(stored), bytes_in=stored_bytes,
            ) as span:
                out = self._backend.detransform(stored, opts)
                if span is not None:
                    # Per-stage byte throughput: stored (transformed) bytes in,
                    # plaintext bytes out.
                    span.attributes["bytes_out"] = sum(len(b) for b in out)
        except Exception as e:
            # Any detransform failure (AuthenticationError on a GCM tag
            # mismatch, CRC/frame errors from the codecs) means the stored
            # bytes are poisoned — re-fetching won't fix them, so quarantine
            # the key instead of letting retries hammer the backend.
            self._quarantine_key(objects_key, f"{type(e).__name__}: {e}")
            raise CorruptChunkException(
                f"Detransform failed for chunks {list(chunk_ids)} of {objects_key}"
            ) from e
        if counters_before is not None:
            dispatches, roundtrips = (
                a - b for a, b in zip(thread_counters(), counters_before)
            )
            flight.note("gcm.windows")
            flight.note("gcm.dispatches", dispatches)
            flight.note("gcm.hbm_roundtrips", roundtrips)
            # Which work class this request's GCM windows submitted under
            # (transform/scheduler.py): breach evidence shows whether
            # latency-class fetch work or a background scrub held the
            # device. Unscoped fetch threads default to latency.
            from tieredstorage_tpu.transform.scheduler import (
                LATENCY,
                current_work_class,
            )

            flight.stage(f"gcm.class:{current_work_class() or LATENCY}")
        if batch_before is not None:
            windows, occupancy_sum, last_batch_id = batch_seam()
            batched = windows - batch_before[0]
            if batched:
                flight.note("gcm.batched_windows", batched)
                flight.note(
                    "gcm.batch_occupancy", occupancy_sum - batch_before[1]
                )
                # The shared-launch marker: records carrying the same
                # gcm.batch:<id> stage rode the SAME device launch.
                flight.stage(f"gcm.batch:{last_batch_id}")
        flight.stage("backend.detransformed")
        if self.on_fetch is not None:
            self.on_fetch(
                (time.monotonic() - start) * 1000.0, sum(len(b) for b in out)
            )
        return out

    def _hedge_attempt(self, objects_key: ObjectKey, chunks, contiguous: bool):
        """Replica-aware hedge: when the fetcher is replicated
        (ReplicatedStorageBackend.read_fetchers), the hedge reads the same
        window from the second-healthiest replica DIRECTLY, so a straggling
        primary replica is raced by a distinct one instead of being hit
        twice. Single-store fetchers return None (the hedge replays `fn`)."""
        read_fetchers = getattr(self._fetcher, "read_fetchers", None)
        if read_fetchers is None:
            return None
        ordered = read_fetchers()
        if len(ordered) < 2:
            return None
        alternate = ordered[1]
        return lambda: self._fetch_stored(
            objects_key, chunks, contiguous, fetcher=alternate
        )

    def _fetch_stored(
        self, objects_key: ObjectKey, chunks, contiguous: bool, *, fetcher=None
    ) -> list[bytes]:
        """Read the stored (transformed) bytes of a chunk window.

        Self-contained and replay-safe — opens, fully reads, and closes its
        own stream(s) — which is exactly the contract the hedger needs: a
        discarded losing attempt cannot tear the winner's bytes.
        `fetcher` overrides the configured fetcher for replica-aware hedge
        attempts."""
        if fetcher is None:
            fetcher = self._fetcher
        # ISSUE 19 injection seam: per *attempt* (hedge attempts each count),
        # an `error` fault propagates as a backend failure; `partial` tears
        # the fetched bytes so the GCM tag check below must refuse them.
        torn = faults.fire("storage.read", str(objects_key))
        if contiguous:
            # One ranged GET covering the window on the transformed side.
            whole = BytesRange.of(
                chunks[0].transformed_position,
                chunks[-1].transformed_position + chunks[-1].transformed_size - 1,
            )
            with fetcher.fetch(objects_key, whole) as stream:
                stored = [read_exactly(stream, c.transformed_size) for c in chunks]
        else:
            stored = []
            for c in chunks:
                with fetcher.fetch(objects_key, c.range()) as stream:
                    stored.append(read_exactly(stream, c.transformed_size))
        if torn:
            stored = [faults.mutate(b, torn) for b in stored]
        return stored
