"""Chunk managers: resolve (object key, manifest, chunk id) -> plaintext chunk.

Reference: core/.../fetch/ChunkManager.java:25-29 and
DefaultChunkManager.java:50-66 (ranged fetch of the transformed chunk, then
decrypt/decompress). Extended here with a batch entry point — `get_chunks`
fetches a window of chunks with ONE ranged request (chunks are contiguous on
the stored side) and detransforms them in ONE backend call, which is the unit
of work the TPU backend wants and what cache prefetch windows use.
"""

from __future__ import annotations

import abc
import io
from typing import BinaryIO, Sequence

from tieredstorage_tpu.manifest.segment_manifest import SegmentManifestV1
from tieredstorage_tpu.storage.core import BytesRange, ObjectFetcher, ObjectKey
from tieredstorage_tpu.transform.api import DetransformOptions, TransformBackend
from tieredstorage_tpu.utils.streams import read_exactly


class ChunkManager(abc.ABC):
    @abc.abstractmethod
    def get_chunk(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, chunk_id: int
    ) -> BinaryIO:
        """Plaintext stream of one original-side chunk."""

    def get_chunks(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, chunk_ids: Sequence[int]
    ) -> list[bytes]:
        """Plaintext bytes of several chunks; default loops over get_chunk."""
        return [
            self.get_chunk(objects_key, manifest, cid).read() for cid in chunk_ids
        ]


class DefaultChunkManager(ChunkManager):
    def __init__(self, fetcher: ObjectFetcher, transform_backend: TransformBackend):
        self._fetcher = fetcher
        self._backend = transform_backend

    def get_chunk(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, chunk_id: int
    ) -> BinaryIO:
        return io.BytesIO(self.get_chunks(objects_key, manifest, [chunk_id])[0])

    def get_chunks(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, chunk_ids: Sequence[int]
    ) -> list[bytes]:
        if len(chunk_ids) == 0:
            return []
        index = manifest.chunk_index
        chunks = [index._chunk_at(cid) for cid in chunk_ids]
        contiguous = all(
            chunks[i + 1].id == chunks[i].id + 1 for i in range(len(chunks) - 1)
        )
        if contiguous:
            # One ranged GET covering the whole window on the transformed side.
            whole = BytesRange.of(
                chunks[0].transformed_position,
                chunks[-1].transformed_position + chunks[-1].transformed_size - 1,
            )
            with self._fetcher.fetch(objects_key, whole) as stream:
                stored = []
                for c in chunks:
                    stored.append(read_exactly(stream, c.transformed_size))
        else:
            stored = []
            for c in chunks:
                with self._fetcher.fetch(objects_key, c.range()) as stream:
                    stored.append(read_exactly(stream, c.transformed_size))
        opts = DetransformOptions.from_manifest(manifest)
        return self._backend.detransform(stored, opts)
