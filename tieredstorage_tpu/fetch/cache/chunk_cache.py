"""Caching chunk manager with single-flight population and async prefetch.

Reference: core/.../fetch/cache/ChunkCache.java — `getChunk` computes through
the async cache (miss → delegate fetch+detransform → `cacheChunk`; hit →
`cachedChunkToInputStream`), bounded by `get.timeout.ms` (:76-131); on every
access it asynchronously populates all chunks covering the next
`prefetch.max.size` original bytes (`startPrefetching` :159-184); the cache is
weight-bounded with expire-after-access and a removal listener (:139-157),
running on its own pool (`thread.pool.size`).

Extended TPU-first: `get_chunks` serves whole chunk windows — missing chunks
in a window are fetched with ONE ranged request and detransformed in ONE
batched backend call (the TPU detransform unit), then cached individually.
"""

from __future__ import annotations

import abc
import concurrent.futures
import contextlib
import dataclasses
import io
import logging
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, BinaryIO, Generic, Mapping, Optional, Sequence, TypeVar

from tieredstorage_tpu.config.cache_config import ChunkCacheConfig
from tieredstorage_tpu.fetch.chunk_manager import ChunkManager
from tieredstorage_tpu.manifest.segment_manifest import SegmentManifestV1
from tieredstorage_tpu.storage.core import ObjectKey
from tieredstorage_tpu.transform.scheduler import (
    current_work_class,
    is_speculative,
    speculative_scope,
    work_class_scope,
)
from tieredstorage_tpu.utils import flightrecorder as flight
from tieredstorage_tpu.utils.caching import LoadingCache, RemovalCause
from tieredstorage_tpu.utils.deadline import check_deadline, remaining_s
from tieredstorage_tpu.utils.locks import new_lock, new_unguarded
from tieredstorage_tpu.utils.tracing import NOOP_TRACER

log = logging.getLogger(__name__)

T = TypeVar("T")


@dataclasses.dataclass(frozen=True)
class ChunkKey:
    """Cache key: segment object file name + chunk id (reference
    fetch/ChunkKey.java:22-64); `path` is the on-disk cache file name."""

    segment_file_name: str
    chunk_id: int

    @classmethod
    def of(cls, object_key: ObjectKey, chunk_id: int) -> "ChunkKey":
        return cls(object_key.value.rsplit("/", 1)[-1], chunk_id)

    @property
    def path(self) -> str:
        return f"{self.segment_file_name}-{self.chunk_id}"


class ChunkCacheTimeoutException(RuntimeError):
    pass


class ChunkCache(ChunkManager, Generic[T], abc.ABC):
    """Wraps a delegate ChunkManager; subclasses define the cached form T
    (bytes in memory, Path on disk)."""

    #: Span recorder; the RSM swaps in its configured tracer.
    tracer = NOOP_TRACER
    #: Optional latency hook `(elapsed_ms)` per window read; the RSM wires it
    #: to Metrics.record_cache_get.
    on_get = None
    #: Synthetic-record source for pool-side prefetch loads; the RSM wires
    #: its configured FlightRecorder so prefetch windows appear on
    #: /debug/requests and as attributable timeline flows instead of gaps.
    flight_recorder = flight.NOOP_RECORDER

    def __init__(self, delegate: ChunkManager) -> None:
        self._delegate = delegate
        self._config: Optional[ChunkCacheConfig] = None
        self._cache: Optional[LoadingCache[ChunkKey, T]] = None
        self._executor: Optional[ThreadPoolExecutor] = None
        #: Times a cache failure (I/O error or wedged load) was bypassed by
        #: fetching straight from the delegate instead of failing the read.
        #: Deliberately lock-free (new_unguarded, races checker): best-effort
        #: degradation tallies bumped on reader/pool threads — a torn update
        #: under-counts one rare failure, which is not worth a lock on the
        #: degraded read path.
        self.degradations = new_unguarded("chunk_cache.ChunkCache.degradations", 0)
        #: Background prefetch loads that failed; never propagated.
        self.prefetch_failures = new_unguarded(
            "chunk_cache.ChunkCache.prefetch_failures", 0
        )
        #: Per-chunk single-flight across readers AND the async prefetch:
        #: a chunk whose fetch+detransform is in flight (delegate call
        #: issued, cache entry not yet registered) has a Future[bytes]
        #: here, so a concurrent reader JOINS the in-flight decode instead
        #: of duplicating it. Critical for slow detransforms (tpu-lzhuff-v1
        #: frames cost ~0.4 s/chunk on the host fallback, BENCH_r05's
        #: 435 ms ranged-fetch p99): without the join, a foreground read
        #: of a chunk the prefetch was already decoding re-decoded it from
        #: scratch while contending for the same cores.
        self._inflight: dict[ChunkKey, "concurrent.futures.Future[bytes]"] = {}
        self._inflight_lock = new_lock("chunk_cache.ChunkCache._inflight_lock")
        #: Readers that joined another reader's in-flight chunk load.
        self.inflight_joins = 0

    # ------------------------------------------------------------------ setup
    def configure(self, configs: Mapping[str, Any]) -> None:
        self._config = self._parse_config(configs)
        self._executor = ThreadPoolExecutor(
            max_workers=self._config.thread_pool_size or None,
            thread_name_prefix="chunk-cache",
        )
        self._cache = LoadingCache(
            executor=self._executor,
            max_weight=self._config.cache_size,
            weigher=self.weight_of,
            expire_after_access_s=self._config.retention_s,
            removal_listener=self.on_removal,
        )

    def _parse_config(self, configs: Mapping[str, Any]) -> ChunkCacheConfig:
        return ChunkCacheConfig(configs)

    @property
    def stats(self):
        return self._cache.stats

    @property
    def size(self) -> int:
        return len(self._cache)

    @property
    def total_weight(self) -> int:
        return self._cache.total_weight

    @property
    def executor(self) -> ThreadPoolExecutor:
        return self._executor

    def close(self) -> None:
        # Drain in-flight loads before returning: callers close the transform
        # backend right after, and a loader thread must not reach a closed
        # backend (delegate.get_chunks -> backend.detransform).
        if self._executor is not None:
            self._executor.shutdown(wait=True, cancel_futures=True)
        # Chain down the tier stack (DeviceHotCache releases its retained
        # device buffers, PeerChunkCache its peer clients); lower tiers'
        # close() is idempotent, so the RSM's explicit peer-cache close
        # stays safe.
        if hasattr(self._delegate, "close"):
            self._delegate.close()

    # ------------------------------------------------------------------ reads
    def get_chunk(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, chunk_id: int
    ) -> BinaryIO:
        data = self.get_chunks(objects_key, manifest, [chunk_id])[0]
        return io.BytesIO(data)

    def get_chunks(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, chunk_ids: Sequence[int]
    ) -> list[bytes]:
        """Window read: missing chunks of the window load through ONE delegate
        batch (single ranged GET + one batched detransform), cached chunks are
        served from the cache; single-flight is preserved per chunk and the
        whole window is bounded by ONE `get.timeout.ms` deadline."""
        if not chunk_ids:
            return []
        start = time.monotonic()
        with self.tracer.span("cache.get_chunks", chunks=len(chunk_ids)):
            out = self._get_chunks_timed(objects_key, manifest, chunk_ids)
        if self.on_get is not None:
            self.on_get((time.monotonic() - start) * 1000.0)
        return out

    def _get_chunks_timed(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, chunk_ids: Sequence[int]
    ) -> list[bytes]:
        # The window wait is bounded by the tighter of `get.timeout.ms` and
        # the ambient end-to-end Deadline; an already-expired deadline fails
        # fast before any loader is scheduled.
        check_deadline(f"cache window read of {objects_key}")
        deadline = time.monotonic() + self._config.get_timeout_s
        ambient = remaining_s()
        if ambient is not None:
            deadline = min(deadline, time.monotonic() + ambient)
        self._start_prefetching(objects_key, manifest, chunk_ids[-1])
        futures = self._populate_window(objects_key, manifest, chunk_ids, deadline)
        out: dict[int, bytes] = {}
        fallback: list[int] = []
        for cid in chunk_ids:
            chunk_key = ChunkKey.of(objects_key, cid)
            kind, future = futures[cid]
            if kind == "bytes":
                # Joined another reader's in-flight fetch+detransform (most
                # often the async prefetch): the future resolves straight to
                # plaintext bytes. A wedged or failed owner must not fail
                # THIS read — degrade to a direct fetch, where the
                # authoritative error (if any) surfaces on our own call.
                try:
                    out[cid] = self._await(future, deadline, cid, objects_key)
                except ChunkCacheTimeoutException:
                    self.degradations += 1
                    fallback.append(cid)
                except Exception:
                    fallback.append(cid)
                continue
            try:
                value = self._await(future, deadline, cid, objects_key)
            except ChunkCacheTimeoutException:
                # Another reader's wedged population (the delegate fetch of
                # THIS window is bounded separately in _populate_window) must
                # not fail this read: degrade to a direct fetch.
                self.degradations += 1
                fallback.append(cid)
                continue
            except OSError:
                # The loader only persists already-fetched bytes, so an error
                # here is cache-storage I/O (unwritable disk cache directory,
                # full disk): bypass the cache for this chunk.
                log.warning("Chunk cache store failed for %s; bypassing cache",
                            chunk_key, exc_info=True)
                self._cache.invalidate(chunk_key)
                self.degradations += 1
                fallback.append(cid)
                continue
            try:
                data = self._read_cached(value)
            except OSError:
                log.warning("Chunk cache read failed for %s; bypassing cache",
                            chunk_key, exc_info=True)
                self.degradations += 1
                data = None
            if data is None:  # evicted + unlinked between resolve and open
                self._cache.invalidate(chunk_key)
                fallback.append(cid)
            else:
                out[cid] = data
        if fallback:
            # Eviction races and degraded cache I/O both land here: re-fetch
            # the affected chunks straight from the delegate, without
            # re-caching — going through the cache again would just re-race
            # with its own evictions (or re-hit the broken disk).
            flight.note("cache.fallback", len(fallback))
            refetched = self._delegate.get_chunks(objects_key, manifest, fallback)
            out.update(zip(fallback, refetched))
        return [out[cid] for cid in chunk_ids]

    def _await(self, future, deadline: float, cid: int, objects_key: ObjectKey) -> T:
        try:
            return future.result(max(0.0, deadline - time.monotonic()))
        except concurrent.futures.TimeoutError:
            raise ChunkCacheTimeoutException(
                f"Loading chunk {cid} of {objects_key} timed out"
            ) from None

    def _read_cached(self, value: T) -> Optional[bytes]:
        try:
            with self.cached_chunk_to_stream(value) as stream:
                return stream.read()
        except FileNotFoundError:
            return None

    def _populate_window(
        self,
        objects_key: ObjectKey,
        manifest: SegmentManifestV1,
        chunk_ids: Sequence[int],
        deadline: Optional[float],
    ) -> dict[int, tuple[str, "concurrent.futures.Future"]]:
        """Batch-fetch every not-yet-cached, not-yet-in-flight chunk of the
        window with ONE delegate call, then register per-chunk cache loaders
        that only persist the already-fetched bytes (no network under an
        executor lock). Returns cid -> ("cache", Future[T]) for cached/owned
        chunks and cid -> ("bytes", Future[bytes]) for chunks joined from
        another reader's in-flight load (single-flight: the prefetch and
        concurrent readers share one fetch+detransform per chunk; joiners
        never wait on more than the owner's sub-window).

        With a deadline (synchronous reads) the delegate fetch runs on the
        pool and is awaited with the remaining budget, so `get.timeout.ms`
        bounds a hung storage backend — on timeout the flight stays
        registered and resolves when the delegate returns, so later readers
        still join it instead of piling on. Without a deadline (prefetch —
        already on a pool worker) the fetch runs inline."""
        futures: dict[int, tuple[str, "concurrent.futures.Future"]] = {}
        missing: list[int] = []
        for cid in chunk_ids:
            key = ChunkKey.of(objects_key, cid)
            present = self._cache.peek(key)
            if present is not None:
                futures[cid] = ("cache", present)
                self._cache.get_if_present(key)  # hit + recency
            else:
                missing.append(cid)
        if len(chunk_ids) > len(missing):
            flight.note("tier.chunk_cache", len(chunk_ids) - len(missing))
        own: list[int] = []
        if missing:
            with self._inflight_lock:
                for cid in missing:
                    key = ChunkKey.of(objects_key, cid)
                    in_flight = self._inflight.get(key)
                    if in_flight is not None:
                        futures[cid] = ("bytes", in_flight)
                        self.inflight_joins += 1
                    else:
                        self._inflight[key] = concurrent.futures.Future()
                        own.append(cid)
        joined = len(missing) - len(own)
        if joined:
            flight.note("tier.inflight_join", joined)
        if own:
            if deadline is None:
                futures.update(
                    self._load_owned(objects_key, manifest, own)
                )
            else:
                # The pool worker loads on behalf of THIS request: re-bind
                # its flight record, trace context, work class, and
                # speculative flag across the hop (the request thread blocks
                # right below) so the lower tiers' outcomes land on it, a
                # peer-cache forward carries the request's traceparent — the
                # fleet stitcher joins the owner's /chunk serve records on
                # it — and a readahead window's decrypt keeps its BACKGROUND
                # admission class + speculative-ledger label instead of
                # silently escalating to latency class on the pool thread.
                # The prefetch branch (deadline=None, already on a pool
                # worker) deliberately carries none of these — it outlives
                # the request that triggered it.
                record = flight.current_record()
                traceparent = self.tracer.current_traceparent()
                work_class = current_work_class()
                speculative = is_speculative()
                task = self._executor.submit(
                    self._load_owned_bound, record, traceparent, work_class,
                    speculative, objects_key, manifest, own,
                )
                try:
                    futures.update(
                        task.result(max(0.0, deadline - time.monotonic()))
                    )
                except concurrent.futures.TimeoutError:
                    raise ChunkCacheTimeoutException(
                        f"Fetching chunks {own} of {objects_key} timed out"
                    ) from None
        return futures

    def _load_owned_bound(
        self, record, traceparent, work_class, speculative,
        objects_key, manifest, own,
    ):
        with contextlib.ExitStack() as stack:
            stack.enter_context(flight.bound(record))
            stack.enter_context(self.tracer.continue_trace(traceparent))
            if work_class is not None:
                stack.enter_context(work_class_scope(work_class))
            if speculative:
                stack.enter_context(speculative_scope())
            return self._load_owned(objects_key, manifest, own)

    def _load_owned(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, own: list[int]
    ) -> dict[int, tuple[str, "concurrent.futures.Future"]]:
        """Fetch+detransform the owned chunks with one delegate call, then
        register cache loaders and resolve the in-flight futures (success or
        error) so joiners wake — runs to completion even when the submitting
        reader's window deadline has already expired."""
        try:
            fetched = self._delegate.get_chunks(objects_key, manifest, own)
        except BaseException as e:
            self._finish_flights(objects_key, own, None, e)
            raise
        futures: dict[int, tuple[str, "concurrent.futures.Future"]] = {}
        for cid, data in zip(own, fetched):
            key = ChunkKey.of(objects_key, cid)
            futures[cid] = ("cache", self._cache.get_future(
                key, lambda k=key, d=data: self.cache_chunk(k, d)
            ))
        # Resolve flights AFTER the cache entries exist, so a reader that
        # misses the flight window finds the chunk in the cache.
        self._finish_flights(objects_key, own, dict(zip(own, fetched)), None)
        return futures

    def _finish_flights(
        self,
        objects_key: ObjectKey,
        own: list[int],
        results: Optional[dict[int, bytes]],
        error: Optional[BaseException],
    ) -> None:
        popped: list[tuple[int, "concurrent.futures.Future"]] = []
        with self._inflight_lock:
            for cid in own:
                flight = self._inflight.pop(ChunkKey.of(objects_key, cid), None)
                if flight is not None:
                    popped.append((cid, flight))
        # Wake joiners outside the lock.
        for cid, flight in popped:
            if error is not None:
                flight.set_exception(error)
            else:
                flight.set_result(results[cid])

    # --------------------------------------------------------------- prefetch
    def _start_prefetching(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1, current_chunk_id: int
    ) -> None:
        prefetch_bytes = self._config.prefetch_max_size
        if prefetch_bytes <= 0:
            return
        index = manifest.chunk_index
        current = index._chunk_at(current_chunk_id)
        start = current.original_position + current.original_size
        if start >= index.original_file_size:
            return
        end = min(start + prefetch_bytes - 1, index.original_file_size - 1)
        first = index.find_chunk_for_original_offset(start)
        last = index.find_chunk_for_original_offset(end)
        ids = [
            cid
            for cid in range(first.id, last.id + 1)
            if self._cache.peek(ChunkKey.of(objects_key, cid)) is None
        ]
        if not ids:
            return
        # Fire-and-forget: one batched load covers the whole prefetch window
        # (deadline=None — already on a pool worker, fetch runs inline there).
        # The originating request's trace id rides along so the pool-side
        # load's synthetic flight record is attributable to its stream.
        self._executor.submit(
            self._prefetch_window, objects_key, manifest, ids,
            flight.current_trace_id() or "",
        )

    def _prefetch_window(
        self, objects_key: ObjectKey, manifest: SegmentManifestV1,
        ids: Sequence[int], origin_trace_id: str = "",
    ) -> None:
        """Isolation boundary: a failed prefetch is counted, never raised —
        and the LoadingCache drops failed loads, so the entries stay clean
        for the next foreground get.

        The range is decoded in `prefetch.window.chunks`-sized sub-windows
        rather than one monolithic batch: each sub-window's chunks become
        servable (cache entries + resolved flights) as soon as IT finishes,
        and a foreground read that joins an in-flight prefetch chunk waits
        for one sub-window's fetch+detransform, not the whole prefetch
        range — which is what keeps slow decodes (tpu-lzhuff-v1) from
        poisoning ranged-fetch p99."""
        try:
            # Prefetch runs on a pool worker: its spans are roots of their own
            # trace (the requesting thread's context is deliberately not
            # captured — the prefetch outlives the request). But the work is
            # NOT anonymous: it opens a synthetic flight record stamped with
            # the originating stream's trace id, so /debug/timeline and
            # assemble_trace show prefetch flows joined to their stream.
            window = self._config.prefetch_window_chunks or len(ids)
            with self.flight_recorder.request(
                "cache.prefetch", trace_id=origin_trace_id
            ):
                flight.note("prefetch.chunks", len(ids))
                flight.stage(
                    f"prefetch.segment:{objects_key.value.rsplit('/', 1)[-1]}"
                )
                with self.tracer.span("cache.prefetch", chunks=len(ids)):
                    for i in range(0, len(ids), max(1, window)):
                        self._populate_window(
                            objects_key, manifest, ids[i : i + max(1, window)],
                            None,
                        )
        except Exception:
            self.prefetch_failures += 1
            self.tracer.event("cache.prefetch_failure", chunks=len(ids))
            log.debug("Prefetch of chunks %s of %s failed", list(ids), objects_key,
                      exc_info=True)

    # ------------------------------------------------------------- subclasses
    @abc.abstractmethod
    def cache_chunk(self, chunk_key: ChunkKey, chunk: bytes) -> T:
        """Persist the plaintext chunk in the cached form."""

    @abc.abstractmethod
    def cached_chunk_to_stream(self, cached: T) -> BinaryIO:
        """Reopen a cached chunk as a readable stream."""

    @abc.abstractmethod
    def weight_of(self, cached: T) -> int:
        """Weight of a cached chunk for the size bound."""

    def on_removal(self, chunk_key: ChunkKey, cached: T, cause: RemovalCause) -> None:
        """Removal listener; disk cache deletes the file here."""
