"""In-memory chunk cache: cached form is the plaintext bytes themselves.

Reference: core/.../fetch/cache/MemoryChunkCache.java (weigher = byte length).
"""

from __future__ import annotations

import io
from typing import BinaryIO

from tieredstorage_tpu.fetch.cache.chunk_cache import ChunkCache, ChunkKey


class MemoryChunkCache(ChunkCache[bytes]):
    def cache_chunk(self, chunk_key: ChunkKey, chunk: bytes) -> bytes:
        return chunk

    def cached_chunk_to_stream(self, cached: bytes) -> BinaryIO:
        return io.BytesIO(cached)

    def weight_of(self, cached: bytes) -> int:
        return len(cached)
