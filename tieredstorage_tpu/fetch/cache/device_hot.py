"""Device-resident hot-window cache: decrypt once, serve many.

The massed-replay shape the reference serves with Caffeine caches + prefetch
(SURVEY L3) — hundreds of consumers re-reading the same hot segment — pays a
full detransform per cold fetch here too. But this build owns something the
reference never had: accelerator memory. After a cold window decrypt, the
PR-8/9 packed ``uint8[B, n_bytes+16]`` output buffer is ALREADY
device-resident (and, under a `MeshPlan`, already row-sharded across the
local chips, so the aggregate HBM of the mesh is one cache); this tier
retains it under an HBM byte budget (``cache.device.bytes``) together with a
pinned host mirror of the window's plaintext, so a hot-key storm costs ONE
transform and N ranged slices — ZERO further GCM dispatches, provable with
``ops.gcm.device_dispatches()``.

Layering (`fetch/factory.py`)::

    ChunkCache (local, per-instance)
      -> DeviceHotCache (this module: hot window serve | delegate + admit)
        -> PeerChunkCache (fleet mode) -> DefaultChunkManager -> storage

A fleet sibling's ``GET /chunk`` forward runs the owner's full chunk path,
so a forwarded hot window is served from the owner's hot tier the same way.

Admission is Zipf-aware, Caffeine/TinyLFU style: a window is admitted on its
SECOND touch (``cache.device.admission.hits``) as counted by a count-min
`FrequencySketch` with periodic halving, and under budget pressure a
candidate only displaces the LRU victim when its sketch frequency is at
least the victim's — one-shot scans can never wash out the hot set.

Capture plumbing: the tier arms a THREAD-LOCAL capture scope around its
delegate call; `TpuTransformBackend._decrypt_batch` offers every verified
decrypt window through ``offer_decrypt_window`` (wired as the backend's
``on_decrypt_window`` hook) and `DefaultChunkManager` notes the window's
`DetransformOptions` through ``note_detransform``. The device buffer is
retained only when the decrypt output rows ARE the final plaintext
(encryption without compression — for compressed segments the rows are
still-compressed frames, so only the host mirror is kept). A retained
buffer is never the donated operand of a later launch: decrypt donates the
STAGED ciphertext input, the output allocation is fresh per window
(``is_deleted()`` stays False — the donation probe, asserted in tests and
``make hot-demo``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import io
import threading
import zlib
from collections import OrderedDict
from typing import Any, BinaryIO, Optional, Sequence

import numpy as np

from tieredstorage_tpu.fetch.chunk_manager import ChunkManager
from tieredstorage_tpu.utils import flightrecorder as flightrec
from tieredstorage_tpu.utils.locks import new_lock, note_mutation
from tieredstorage_tpu.utils.tracing import NOOP_TRACER

#: Extra columns of the packed device buffer past the payload (the tag).
_TAG_COLUMNS = 16


# -------------------------------------------------------- capture plumbing
_CAPTURE = threading.local()


class _CaptureState:
    """Per-thread capture slot for decrypt windows offered by the transform
    backend while THIS thread is inside the hot tier's delegate call."""

    __slots__ = ("armed", "windows", "opts")

    def __init__(self) -> None:
        self.armed = False
        self.windows: list[tuple[Any, tuple[int, ...], int, int]] = []
        self.opts = None


def _capture_state() -> _CaptureState:
    state = getattr(_CAPTURE, "state", None)
    if state is None:
        state = _CaptureState()
        _CAPTURE.state = state
    return state


class CapturedDecrypt:
    """What a capture scope saw: the decrypt windows offered under the
    delegate call plus the noted DetransformOptions (filled at scope exit,
    so it stays valid after the thread-local slot is restored)."""

    __slots__ = ("windows", "opts")

    def __init__(self) -> None:
        self.windows: list[tuple[Any, tuple[int, ...], int, int]] = []
        self.opts = None


@contextlib.contextmanager
def capture_scope():
    """Arm the calling thread's decrypt-window capture for the duration of
    a delegate call (re-entrant: the previous slot is restored on exit, so
    a hot tier nested under another instance's serve path stays correct).
    Yields a `CapturedDecrypt` snapshot that is filled when the scope
    exits."""
    state = _capture_state()
    prev = (state.armed, state.windows, state.opts)
    state.armed, state.windows, state.opts = True, [], None
    captured = CapturedDecrypt()
    try:
        yield captured
    finally:
        captured.windows = state.windows
        captured.opts = state.opts
        state.armed, state.windows, state.opts = prev


def offer_decrypt_window(device, sizes, n_bytes: int, mesh_size: int = 1) -> None:
    """`TpuTransformBackend.on_decrypt_window` target: called with the
    still-device-resident packed output of a VERIFIED decrypt window
    (``uint8[B(+pad), n_bytes+16]``, row-sharded under a mesh). Dropped
    unless the calling thread armed a capture scope — unrelated decrypts
    (scrubber passes, sibling requests) never leak into a window."""
    state = getattr(_CAPTURE, "state", None)
    if state is not None and state.armed:
        state.windows.append((device, tuple(sizes), int(n_bytes), int(mesh_size)))


def note_detransform(opts) -> None:
    """`DefaultChunkManager.on_detransform` target: the DetransformOptions
    of the window being decoded, so admission can tell whether the decrypt
    rows are the final plaintext (no compression stage follows)."""
    state = getattr(_CAPTURE, "state", None)
    if state is not None and state.armed:
        state.opts = opts


# -------------------------------------------------------- frequency sketch
#: Distinct CRC salts, one per sketch row.
_SKETCH_SEEDS = (0x9E3779B9, 0x85EBCA6B, 0xC2B2AE35, 0x27D4EB2F)


class FrequencySketch:
    """Count-min popularity sketch with saturating counters and periodic
    halving (TinyLFU aging), the Zipf-aware half of admission: estimates
    stay proportional to RECENT touch frequency, so yesterday's hot set
    decays instead of squatting on the budget forever. Deterministic
    (CRC32 row hashes), so seeded tests and demos reproduce exactly."""

    ROWS = 4
    MAX_COUNT = 255

    def __init__(self, width: int = 4096, decay_every: Optional[int] = None):
        if width < 1:
            raise ValueError(f"sketch width must be >= 1, got {width}")
        # Power-of-two width keeps the column mask a single AND.
        self._width = 1 << max(0, (width - 1).bit_length())
        self._mask = self._width - 1
        self._counts = np.zeros((self.ROWS, self._width), dtype=np.uint16)
        #: Touches between halvings; ~8x width keeps estimates fresh
        #: without losing the hot set's lead over one-shot scans.
        self._decay_every = decay_every if decay_every else self._width * 8
        self._ops = 0
        self._lock = new_lock("device_hot.FrequencySketch._lock")

    @property
    def width(self) -> int:
        return self._width

    def _columns(self, key: str) -> list[int]:
        data = key.encode()
        return [zlib.crc32(data, seed) & self._mask for seed in _SKETCH_SEEDS]

    def touch(self, key: str) -> int:
        """Count one touch; returns the post-touch estimate (min over rows,
        the count-min bound)."""
        columns = self._columns(key)
        with self._lock:
            self._ops += 1
            note_mutation("device_hot.FrequencySketch._ops")
            if self._ops >= self._decay_every:
                self._ops = 0
                self._counts >>= 1
                note_mutation("device_hot.FrequencySketch._counts")
            estimate = self.MAX_COUNT
            for row, col in enumerate(columns):
                value = int(self._counts[row, col])
                if value < self.MAX_COUNT:
                    value += 1
                    self._counts[row, col] = value
                    note_mutation("device_hot.FrequencySketch._counts")
                estimate = min(estimate, value)
            return estimate

    def estimate(self, key: str) -> int:
        columns = self._columns(key)
        with self._lock:
            return min(int(self._counts[row, col]) for row, col in enumerate(columns))


# ------------------------------------------------------------- hot windows
@dataclasses.dataclass
class HotWindow:
    """One admitted decrypt window: the pinned host mirror (serve source)
    plus, when the decrypt rows are the plaintext, the retained
    device-resident packed buffer (HBM half of the budget)."""

    key: str                      # "<segment file>#<lo>-<hi>"
    file: str
    chunk_ids: tuple[int, ...]
    mirror: np.ndarray            # uint8 view over the concatenated plaintext
    offsets: tuple[int, ...]      # per-chunk start into the mirror
    lens: tuple[int, ...]
    device: Any = None            # uint8[B(+pad), n_bytes+16] or None
    device_nbytes: int = 0
    n_bytes: int = 0              # payload columns of the device buffer
    mesh_size: int = 1

    def __post_init__(self) -> None:
        self._row = {cid: i for i, cid in enumerate(self.chunk_ids)}

    @property
    def nbytes(self) -> int:
        return int(self.mirror.nbytes) + int(self.device_nbytes)

    def row_of(self, chunk_id: int) -> int:
        return self._row[chunk_id]

    def covers(self, chunk_id: int) -> bool:
        return chunk_id in self._row

    def chunk(self, chunk_id: int) -> bytes:
        """Copying ranged slice of the pinned host mirror (tests and
        callers that need owned bytes)."""
        return self.chunk_view(chunk_id).tobytes()

    def chunk_view(self, chunk_id: int) -> memoryview:
        """ZERO-COPY ranged slice of the pinned host mirror — the hot
        serve (ISSUE 13 satellite, ROADMAP item 3 remainder). The gateway
        streams the view straight to the socket; no per-chunk ``tobytes``
        copy. The view holds the mirror's buffer alive (numpy refcount),
        so an eviction racing a serve can never tear the bytes — at the
        cost of the mirror lingering while any served view is retained."""
        i = self._row[chunk_id]
        off = self.offsets[i]
        return memoryview(self.mirror)[off : off + self.lens[i]]


def _file_of(objects_key) -> str:
    """Cache key half, matching ChunkKey.of: the object file name."""
    return objects_key.value.rsplit("/", 1)[-1]


def _window_key(file: str, chunk_ids: Sequence[int]) -> str:
    return f"{file}#{chunk_ids[0]}-{chunk_ids[-1]}"


class DeviceHotCache(ChunkManager):
    """ChunkManager tier retaining the hottest decrypted windows resident
    (device buffer + pinned host mirror) under ``cache.device.bytes``."""

    #: Span/event recorder; the RSM swaps in its configured tracer.
    tracer = NOOP_TRACER

    def __init__(
        self,
        delegate: ChunkManager,
        transform_backend=None,
        *,
        innermost=None,
        budget_bytes: int = 0,
        admission_hits: int = 2,
        sketch_width: int = 4096,
        tracer=None,
    ) -> None:
        self._delegate = delegate
        self._backend = transform_backend
        self.budget_bytes = int(budget_bytes)
        self.admission_hits = max(1, int(admission_hits))
        if tracer is not None:
            self.tracer = tracer
        self._sketch = FrequencySketch(sketch_width)
        self._lock = new_lock("device_hot.DeviceHotCache._lock")
        #: window key -> HotWindow, LRU order (first = coldest).
        self._windows: "OrderedDict[str, HotWindow]" = OrderedDict()
        #: (segment file, chunk id) -> window key of the NEWEST cover.
        self._resident: dict[tuple[str, int], str] = {}
        self._bytes = 0
        self._device_bytes = 0
        # Counters (exported as hot-cache-metrics gauges).
        self.hits = 0
        self.misses = 0
        self.chunks_served = 0
        #: Chunks served as zero-copy memoryview slices of a pinned mirror
        #: (every hot hit; the `make hot-demo` zero-copy gate).
        self.zero_copy_serves = 0
        self.admissions = 0
        self.rejections = 0
        self.evictions = 0
        self.device_windows = 0
        # Wire the capture hooks: the backend offers verified decrypt
        # windows, the innermost manager notes the DetransformOptions.
        if transform_backend is not None and hasattr(
            transform_backend, "on_decrypt_window"
        ):
            transform_backend.on_decrypt_window = offer_decrypt_window
        if innermost is not None and hasattr(innermost, "on_detransform"):
            innermost.on_detransform = note_detransform

    # ------------------------------------------------------------ accessors
    @property
    def delegate(self) -> ChunkManager:
        return self._delegate

    @property
    def resident_windows(self) -> int:
        with self._lock:
            return len(self._windows)

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes

    @property
    def resident_device_bytes(self) -> int:
        with self._lock:
            return self._device_bytes

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def window(self, objects_key, chunk_id: int) -> Optional[HotWindow]:
        """The resident window covering (key, chunk id), if any (tests,
        demos, and the donation probe)."""
        file = _file_of(objects_key)
        with self._lock:
            wkey = self._resident.get((file, chunk_id))
            return self._windows.get(wkey) if wkey is not None else None

    def close(self) -> None:
        with self._lock:
            self._windows.clear()
            self._resident.clear()
            self._bytes = 0
            self._device_bytes = 0
            self.device_windows = 0
            note_mutation("device_hot.DeviceHotCache.device_windows")
        if hasattr(self._delegate, "close"):
            self._delegate.close()

    # ----------------------------------------------------------------- reads
    def get_chunk(
        self, objects_key, manifest, chunk_id: int
    ) -> BinaryIO:
        return io.BytesIO(self.get_chunks(objects_key, manifest, [chunk_id])[0])

    def get_chunks(self, objects_key, manifest, chunk_ids: Sequence[int]) -> list[bytes]:
        if not chunk_ids:
            return []
        file = _file_of(objects_key)
        served = self._serve_hot(file, chunk_ids)
        if served is not None:
            # Hits count toward the window's sketch frequency too (TinyLFU
            # counts ACCESSES): a long-resident hot window keeps its lead
            # over one-shot scan candidates at eviction time.
            self._sketch.touch(_window_key(file, chunk_ids))
            self.tracer.event(
                "hot.hit", key=objects_key.value, chunks=len(chunk_ids)
            )
            flightrec.note("tier.device_hot", len(chunk_ids))
            return served
        with capture_scope() as captured:
            chunks = self._delegate.get_chunks(objects_key, manifest, list(chunk_ids))
        self._maybe_admit(file, tuple(chunk_ids), chunks, captured)
        return chunks

    def _serve_hot(self, file: str, chunk_ids: Sequence[int]) -> Optional[list]:
        """Serve the window from resident covers as ZERO-COPY memoryview
        slices of the pinned mirrors, or None on any gap. Window objects
        are collected under the lock and sliced outside it — an eviction
        racing the serve cannot tear bytes (each view keeps its mirror's
        buffer alive)."""
        covers: list[HotWindow] = []
        with self._lock:
            for cid in chunk_ids:
                wkey = self._resident.get((file, cid))
                if wkey is None:
                    self.misses += 1
                    note_mutation("device_hot.DeviceHotCache.misses")
                    return None
                covers.append(self._windows[wkey])
            for wkey in dict.fromkeys(w.key for w in covers):
                self._windows.move_to_end(wkey)
            self.hits += 1
            self.chunks_served += len(chunk_ids)
            self.zero_copy_serves += len(chunk_ids)
            note_mutation("device_hot.DeviceHotCache.hits")
        return [w.chunk_view(cid) for w, cid in zip(covers, chunk_ids)]

    def device_rows(self, objects_key, chunk_ids: Sequence[int]):
        """Device-side ranged slicing: the retained rows for `chunk_ids` as
        still-device-resident arrays (``uint8[n_bytes+16]`` each), or None
        when any chunk lacks a device-backed cover. Zero GCM dispatches —
        a pure gather on the resident buffer; materializing the result is
        the CALLER's choice (and the dispatch checker's concern inside the
        fused-window closure)."""
        file = _file_of(objects_key)
        rows: list[tuple[HotWindow, int]] = []
        with self._lock:
            for cid in chunk_ids:
                wkey = self._resident.get((file, cid))
                if wkey is None:
                    return None
                w = self._windows[wkey]
                if w.device is None:
                    return None
                rows.append((w, w.row_of(cid)))
        return [w.device[row] for w, row in rows]

    # ------------------------------------------------------------- admission
    def _maybe_admit(
        self,
        file: str,
        chunk_ids: tuple[int, ...],
        chunks: list[bytes],
        captured: CapturedDecrypt,
    ) -> None:
        if self.budget_bytes <= 0:
            return
        wkey = _window_key(file, chunk_ids)
        frequency = self._sketch.touch(wkey)
        with self._lock:
            if wkey in self._windows:
                self._windows.move_to_end(wkey)
                return
        if frequency < self.admission_hits:
            # Below the promotion threshold (first touch of a cold window):
            # the sketch remembers, the budget is not spent.
            with self._lock:
                self.rejections += 1
                note_mutation("device_hot.DeviceHotCache.rejections")
            return
        window = self._build_window(wkey, file, chunk_ids, chunks, captured)
        if window.nbytes > self.budget_bytes:
            with self._lock:
                self.rejections += 1
                note_mutation("device_hot.DeviceHotCache.rejections")
            self.tracer.event("hot.reject", window=wkey, bytes=window.nbytes)
            return
        evicted: list[str] = []
        with self._lock:
            if wkey in self._windows:  # racing admitter won; keep theirs
                self._windows.move_to_end(wkey)
                return
            while self._bytes + window.nbytes > self.budget_bytes:
                victim_key = next(iter(self._windows))
                if self._sketch.estimate(victim_key) > frequency:
                    # TinyLFU gate: the LRU victim is still hotter than the
                    # candidate — a one-shot scan must not wash out the set.
                    self.rejections += 1
                    note_mutation("device_hot.DeviceHotCache.rejections")
                    return
                self._evict_locked(victim_key)
                evicted.append(victim_key)
            self._windows[wkey] = window
            for cid in chunk_ids:
                self._resident[(file, cid)] = wkey
            self._bytes += window.nbytes
            self._device_bytes += window.device_nbytes
            if window.device is not None:
                self.device_windows += 1
                note_mutation("device_hot.DeviceHotCache.device_windows")
            self.admissions += 1
            note_mutation("device_hot.DeviceHotCache.admissions")
        for victim_key in evicted:
            self.tracer.event("hot.evict", window=victim_key)
        self.tracer.event(
            "hot.admit", window=wkey, bytes=window.nbytes,
            device=window.device is not None,
        )

    def _evict_locked(self, victim_key: str) -> None:
        """Drop the coldest window (caller holds ``_lock``). Index entries
        are removed only while still pointing at the victim — a newer
        overlapping window keeps its covers."""
        victim = self._windows.pop(victim_key)
        for cid in victim.chunk_ids:
            if self._resident.get((victim.file, cid)) == victim_key:
                del self._resident[(victim.file, cid)]
        self._bytes -= victim.nbytes
        self._device_bytes -= victim.device_nbytes
        if victim.device is not None:
            self.device_windows -= 1
            note_mutation("device_hot.DeviceHotCache.device_windows")
        self.evictions += 1
        note_mutation("device_hot.DeviceHotCache.evictions")

    def _build_window(
        self,
        wkey: str,
        file: str,
        chunk_ids: tuple[int, ...],
        chunks: list[bytes],
        captured: CapturedDecrypt,
    ) -> HotWindow:
        """Pinned host mirror always; the device half only when exactly one
        decrypt window was captured under this call AND its rows are the
        final plaintext (no compression stage followed the decrypt, and the
        per-row sizes match the returned chunks)."""
        lens = tuple(len(c) for c in chunks)
        offsets = []
        position = 0
        for n in lens:
            offsets.append(position)
            position += n
        mirror = np.frombuffer(b"".join(chunks), dtype=np.uint8)
        device = None
        device_nbytes = 0
        n_bytes = 0
        mesh_size = 1
        opts = captured.opts
        if (
            len(captured.windows) == 1
            and opts is not None
            and not opts.compression
        ):
            buffer, sizes, cap_n_bytes, cap_mesh = captured.windows[0]
            deleted = getattr(buffer, "is_deleted", None)
            if sizes == lens and not (deleted is not None and deleted()):
                device = buffer
                n_bytes = cap_n_bytes
                mesh_size = cap_mesh
                device_nbytes = int(
                    getattr(buffer, "nbytes", 0)
                    or len(lens) * (cap_n_bytes + _TAG_COLUMNS)
                )
        return HotWindow(
            key=wkey, file=file, chunk_ids=chunk_ids,
            mirror=mirror, offsets=tuple(offsets), lens=lens,
            device=device, device_nbytes=device_nbytes,
            n_bytes=n_bytes, mesh_size=mesh_size,
        )


def _definition():
    """ConfigDef of the hot-tier keys `ChunkManagerFactoryConfig` reads —
    rendered into docs/configs.rst (the generated-docs drift gate in
    `make analyze` keeps it in sync with the committed file)."""
    from tieredstorage_tpu.config.configdef import ConfigDef, ConfigKey, in_range

    d = ConfigDef()
    d.define(ConfigKey(
        "cache.device.bytes", "long", default=0, validator=in_range(0, None),
        importance="medium",
        doc="HBM byte budget of the device-resident hot-window cache tier "
            "(retained decrypt buffers plus their pinned host mirrors). 0 "
            "(default) disables the tier. Under a transform mesh the "
            "retained rows stay sharded across the local chips, so the "
            "budget spans the mesh's aggregate HBM.",
    ))
    d.define(ConfigKey(
        "cache.device.admission.hits", "int", default=2,
        validator=in_range(1, None), importance="low",
        doc="Sketch touches a window needs before it is admitted "
            "(second-hit promotion by default: one-shot scans are never "
            "retained).",
    ))
    d.define(ConfigKey(
        "cache.device.sketch.width", "int", default=4096,
        validator=in_range(16, None), importance="low",
        doc="Columns per row of the count-min frequency sketch driving "
            "Zipf-aware admission (rounded up to a power of two; counters "
            "halve every ~8x this many touches).",
    ))
    return d
