from tieredstorage_tpu.fetch.cache.chunk_cache import ChunkCache, ChunkKey
from tieredstorage_tpu.fetch.cache.device_hot import DeviceHotCache, FrequencySketch
from tieredstorage_tpu.fetch.cache.disk import DiskChunkCache
from tieredstorage_tpu.fetch.cache.memory import MemoryChunkCache

__all__ = [
    "ChunkCache",
    "ChunkKey",
    "DeviceHotCache",
    "DiskChunkCache",
    "FrequencySketch",
    "MemoryChunkCache",
]
