"""On-disk chunk cache: write-to-temp + atomic move, delete on eviction.

Reference: core/.../fetch/cache/DiskChunkCache.java — `cacheChunk` writes to
`{path}/temp/{key}` then ATOMIC_MOVEs to `{path}/cache/{key}` (:70-87) so
readers never observe partial files; the removal listener deletes the file
and records freed bytes (:98-115); weigher = file size; the directory pair is
wiped on startup (config/DiskChunkCacheConfig.java:62-73).
"""

from __future__ import annotations

import itertools
import logging
import os
from pathlib import Path
from typing import Any, BinaryIO, Mapping

from tieredstorage_tpu.config.cache_config import DiskChunkCacheConfig
from tieredstorage_tpu.fetch.cache.chunk_cache import ChunkCache, ChunkKey
from tieredstorage_tpu.utils.caching import RemovalCause

log = logging.getLogger(__name__)


class DiskChunkCache(ChunkCache[Path]):
    _config: DiskChunkCacheConfig

    def __init__(self, delegate) -> None:
        super().__init__(delegate)
        self._generation = itertools.count()

    def _parse_config(self, configs: Mapping[str, Any]) -> DiskChunkCacheConfig:
        return DiskChunkCacheConfig(configs)

    def cache_chunk(self, chunk_key: ChunkKey, chunk: bytes) -> Path:
        # The generation suffix makes every cache insertion a distinct file:
        # a late removal listener (expiry/eviction runs async) can then never
        # unlink a file belonging to a NEWER entry re-cached under the same
        # ChunkKey — it only ever deletes the exact path it owns.
        name = f"{chunk_key.path}.{next(self._generation)}"
        temp = self._config.temp_path / name
        final = self._config.cache_path / name
        try:
            with open(temp, "wb") as f:
                f.write(chunk)
            os.replace(temp, final)  # atomic within the cache filesystem
        except OSError:
            # Cache-write I/O errors degrade to cache-bypass upstream
            # (ChunkCache.get_chunks); don't leak the partial temp file.
            try:
                temp.unlink(missing_ok=True)
            except OSError:
                pass
            raise
        self.record_write(len(chunk))
        return final

    def cached_chunk_to_stream(self, cached: Path) -> BinaryIO:
        return open(cached, "rb")

    def weight_of(self, cached: Path) -> int:
        return cached.stat().st_size

    def on_removal(self, chunk_key: ChunkKey, cached: Path, cause: RemovalCause) -> None:
        try:
            size = cached.stat().st_size
            cached.unlink()
            self.record_delete(size)
        except FileNotFoundError:
            pass
        except OSError:
            log.warning("Failed to delete cached chunk file %s", cached, exc_info=True)

    # Metric taps; wired by set_metrics_recorder
    # (reference DiskChunkCacheMetrics.java:38-68).
    def record_write(self, n_bytes: int) -> None:
        if self._metrics_recorder is not None:
            self._metrics_recorder.record_write(n_bytes)

    def record_delete(self, n_bytes: int) -> None:
        if self._metrics_recorder is not None:
            self._metrics_recorder.record_delete(n_bytes)

    _metrics_recorder = None

    def set_metrics_recorder(self, recorder) -> None:
        """Attach a write/delete byte recorder (DiskCacheMetrics)."""
        self._metrics_recorder = recorder
