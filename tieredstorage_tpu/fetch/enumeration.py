"""Ranged fetch over chunks: map an original-byte range to chunk streams.

Reference: core/.../fetch/FetchChunkEnumeration.java — chunk id window from
the chunk index (ctor :54-70), skip into the first chunk and cap the last
(:100-131), lazy stream so early close stops fetching (:160-175; the broker
rarely drains a whole fetch).
"""

from __future__ import annotations

import io
from typing import BinaryIO, Iterator

from tieredstorage_tpu.errors import RemoteResourceNotFoundException
from tieredstorage_tpu.fetch.chunk_manager import ChunkManager
from tieredstorage_tpu.manifest.segment_manifest import SegmentManifestV1
from tieredstorage_tpu.storage.core import BytesRange, KeyNotFoundException, ObjectKey
from tieredstorage_tpu.utils.streams import BoundedStream, LazyConcatStream


class FetchChunkEnumeration:
    def __init__(
        self,
        chunk_manager: ChunkManager,
        objects_key: ObjectKey,
        manifest: SegmentManifestV1,
        byte_range: BytesRange,
    ):
        self._chunk_manager = chunk_manager
        self._key = objects_key
        self._manifest = manifest
        index = manifest.chunk_index

        first_chunk = index.find_chunk_for_original_offset(byte_range.from_position)
        if first_chunk is None:
            raise ValueError(
                f"Invalid start position {byte_range.from_position} "
                f"in segment path {objects_key}"
            )
        self._first_chunk_id = first_chunk.id
        last_offset = min(byte_range.to_position, index.original_file_size - 1)
        self._last_chunk_id = index.find_chunk_for_original_offset(last_offset).id
        self._skip_in_first = byte_range.from_position - first_chunk.original_position
        self._total = min(byte_range.size, index.original_file_size - byte_range.from_position)

    def _parts(self) -> Iterator[BinaryIO]:
        remaining = self._total
        try:
            for chunk_id in range(self._first_chunk_id, self._last_chunk_id + 1):
                data = self._chunk_manager.get_chunks(self._key, self._manifest, [chunk_id])[0]
                if chunk_id == self._first_chunk_id:
                    data = data[self._skip_in_first :]
                if len(data) > remaining:
                    data = data[:remaining]
                remaining -= len(data)
                yield io.BytesIO(data)
        except KeyNotFoundException as e:
            raise RemoteResourceNotFoundException(str(e)) from e

    def to_stream(self) -> BinaryIO:
        return BoundedStream(LazyConcatStream(self._parts()), self._total)
