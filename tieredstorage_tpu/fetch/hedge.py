"""Hedged requests: a second chance for straggling backend fetches.

Dean & Barroso ("The Tail at Scale", CACM 2013 §Hedged requests): when a
request has been outstanding longer than the typical p9x latency, issue the
same request again and take whichever answer lands first. The tail of the
latency distribution is dominated by rare per-request stalls (GC pauses,
connection resets, throttled replicas) that a fresh attempt almost never
repeats, so a hedge converts a p99 stall into roughly p50 + hedge-delay —
at the cost of a bounded amount of extra load.

Two pieces keep the extra load bounded and the semantics safe:

- ``HedgeBudget``: a token bucket earning a fraction of a token per primary
  call and spending one per hedge, so hedges can never exceed the configured
  percentage of primary traffic (``hedge.budget.percent``) — under a
  systemic slowdown (every request slow) hedging self-limits instead of
  doubling the load on an already-struggling backend.
- first-*success*-wins: the loser is cancelled if still queued, and simply
  discarded if already running — each attempt fully reads and closes its own
  response before returning, so a discarded loser can never tear the
  winner's bytes. If the first completion failed, the other attempt's result
  is awaited; only when both fail does the last error propagate.

The hedge delay is a callable so the RSM can wire the observed p95 of the
``chunk-fetch-time-ms`` histogram (PR 2) with a static ``hedge.delay.ms``
fallback until enough samples exist.
"""

from __future__ import annotations

import concurrent.futures
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional, TypeVar

from tieredstorage_tpu.utils import flightrecorder as flight
from tieredstorage_tpu.utils.deadline import current_deadline, deadline_scope
from tieredstorage_tpu.utils.tracing import NOOP_TRACER
from tieredstorage_tpu.utils.locks import new_lock

T = TypeVar("T")


class HedgeBudget:
    """Token bucket bounding hedges to a percentage of primary traffic.

    Earns ``percent/100`` tokens per primary call (capped at `capacity`),
    spends one whole token per hedge; starts with one token so the very
    first straggler can already be hedged."""

    def __init__(self, percent: int, capacity: float = 10.0) -> None:
        if not 0 < percent <= 100:
            raise ValueError(f"hedge budget percent must be in (0, 100], got {percent}")
        self._earn = percent / 100.0
        self._capacity = max(1.0, capacity)
        self._balance = 1.0
        self._lock = new_lock("hedge.HedgeBudget._lock")

    @property
    def balance(self) -> float:
        with self._lock:
            return self._balance

    def deposit(self) -> None:
        with self._lock:
            self._balance = min(self._capacity, self._balance + self._earn)

    def try_spend(self) -> bool:
        with self._lock:
            if self._balance >= 1.0:
                self._balance -= 1.0
                return True
            return False


class Hedger:
    """Runs callables with tail-latency hedging on a private thread pool.

    `delay_s` is consulted per call (so a histogram-driven delay adapts as
    traffic accumulates). Counters are plain ints exported as resilience
    gauges; `on_win` is an optional `(elapsed_ms)` hook the RSM wires to the
    hedge-win-time histogram."""

    def __init__(
        self,
        delay_s: Callable[[], float],
        budget: HedgeBudget,
        *,
        max_workers: int = 8,
        tracer=NOOP_TRACER,
        on_win: Optional[Callable[[float], None]] = None,
    ) -> None:
        self._delay_s = delay_s
        self._budget = budget
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="hedge"
        )
        self.tracer = tracer
        self.on_win = on_win
        #: Primary calls routed through the hedger.
        self.primaries = 0
        #: Hedges actually launched after the delay elapsed.
        self.launched = 0
        #: Calls won by the hedge (the primary was the straggler).
        self.wins = 0
        #: Hedges suppressed because the budget was exhausted.
        self.suppressed = 0
        self._lock = new_lock("hedge.Hedger._lock")

    @property
    def budget(self) -> HedgeBudget:
        return self._budget

    def close(self) -> None:
        self._pool.shutdown(wait=True, cancel_futures=True)

    def call(
        self,
        fn: Callable[[], T],
        *,
        what: str = "",
        hedge_fn: Optional[Callable[[], T]] = None,
    ) -> T:
        """Run `fn`, hedging with a second run after the delay.

        `fn` must be self-contained and replay-safe (a ranged GET that reads
        and closes its own stream) — both attempts may run to completion, and
        exactly one result is returned. The ambient Deadline and the caller's
        trace identity do NOT cross into the pool threads automatically; the
        deadline is re-installed explicitly (it must bound both attempts).

        `hedge_fn`, when given, is what the hedge runs instead of a second
        `fn` — replica-aware hedging hands the equivalent read against a
        *distinct* replica here (ReplicatedStorageBackend.read_fetchers), so
        a straggling replica is raced by a different one rather than being
        hit twice. It must return byte-identical results to `fn`."""
        with self._lock:
            self.primaries += 1
        self._budget.deposit()
        deadline = current_deadline()

        def run(attempt_fn: Callable[[], T] = fn) -> T:
            with deadline_scope(deadline):
                return attempt_fn()

        start = time.monotonic()
        primary = self._pool.submit(run)
        try:
            return primary.result(timeout=max(0.0, self._delay_s()))
        except concurrent.futures.TimeoutError:
            pass
        # Primary is straggling. Spend a hedge token, or wait it out.
        if not self._budget.try_spend():
            with self._lock:
                self.suppressed += 1
            self.tracer.event("fetch.hedge_suppressed", what=what)
            flight.note("hedge.suppressed")
            return primary.result()
        with self._lock:
            self.launched += 1
        # call() runs on the request's (record-bound) thread; only the
        # attempts ride the pool, so the notes land on the right record.
        flight.note("hedge.launched")
        distinct = hedge_fn is not None
        self.tracer.event("fetch.hedged", what=what, distinct_replica=distinct)
        hedge = self._pool.submit(run, hedge_fn) if distinct else self._pool.submit(run)
        pending = {primary, hedge}
        last_error: Optional[BaseException] = None
        while pending:
            done, pending = concurrent.futures.wait(
                pending, return_when=concurrent.futures.FIRST_COMPLETED
            )
            for future in done:
                try:
                    result = future.result()
                except BaseException as e:  # noqa: BLE001 — first SUCCESS wins
                    last_error = e
                    continue
                for loser in pending:
                    # Queued losers are cancelled; a running loser completes
                    # and its result is discarded (its stream is owned and
                    # closed inside fn, so nothing leaks or tears).
                    loser.cancel()
                if future is hedge:
                    with self._lock:
                        self.wins += 1
                    elapsed_ms = (time.monotonic() - start) * 1000.0
                    self.tracer.event("fetch.hedge_won", what=what)
                    flight.note("hedge.won")
                    if self.on_win is not None:
                        self.on_win(elapsed_ms)
                return result
        assert last_error is not None  # both attempts failed
        raise last_error
