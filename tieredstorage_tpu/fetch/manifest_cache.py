"""Segment manifest cache: object key -> parsed SegmentManifest.

Reference: core/.../fetch/manifest/SegmentManifestCache.java:26-29 (interface)
and MemorySegmentManifestCache.java (Caffeine AsyncLoadingCache; defaults
1000 entries / 1 h retention :51-52; `get` with timeout :67-89). Sized by
entry count (the manifests are ~KB JSON), unlike the byte-weighed chunk and
index caches.

``ManifestLookahead`` (ISSUE 18) rides on top: a keyed single-flight
prefetch seam so a sequential read crossing a segment boundary finds the
NEXT segment's manifest already resolving (or resolved) instead of paying
the fetch+parse stall inline — and N readahead streams crossing the same
boundary resolve it ONCE.
"""

from __future__ import annotations

import abc
import concurrent.futures
import logging
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Mapping, Optional

from tieredstorage_tpu.config.cache_config import CacheConfig
from tieredstorage_tpu.manifest.segment_manifest import SegmentManifestV1
from tieredstorage_tpu.storage.core import ObjectKey
from tieredstorage_tpu.utils.caching import LoadingCache
from tieredstorage_tpu.utils.locks import new_lock, note_mutation

log = logging.getLogger(__name__)


class SegmentManifestCache(abc.ABC):
    @abc.abstractmethod
    def get(
        self, key: ObjectKey, loader: Callable[[], SegmentManifestV1]
    ) -> SegmentManifestV1:
        """Cached parsed manifest; loads through `loader` at most once."""


class MemorySegmentManifestCache(SegmentManifestCache):
    DEFAULT_MAX_SIZE = 1000
    DEFAULT_RETENTION_MS = 3_600_000  # 1 h

    def __init__(self) -> None:
        self._cache: Optional[LoadingCache[str, SegmentManifestV1]] = None
        self._config: Optional[CacheConfig] = None
        self._executor: Optional[ThreadPoolExecutor] = None

    def configure(self, configs: Mapping[str, Any]) -> None:
        self._config = CacheConfig(
            configs,
            size_default=self.DEFAULT_MAX_SIZE,
            retention_ms_default=self.DEFAULT_RETENTION_MS,
        )
        self._executor = ThreadPoolExecutor(
            max_workers=self._config.thread_pool_size or None,
            thread_name_prefix="manifest-cache",
        )
        self._cache = LoadingCache(
            executor=self._executor,
            max_weight=self._config.cache_size,
            weigher=lambda _m: 1,  # sized by entry count
            expire_after_access_s=self._config.retention_s,
        )

    @property
    def stats(self):
        return self._cache.stats

    @property
    def size(self) -> int:
        return len(self._cache)

    def get(
        self, key: ObjectKey, loader: Callable[[], SegmentManifestV1]
    ) -> SegmentManifestV1:
        try:
            return self._cache.get(key.value, loader, timeout=self._config.get_timeout_s)
        except concurrent.futures.TimeoutError:
            raise TimeoutError(f"Loading manifest {key.value} timed out") from None

    def close(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=True, cancel_futures=True)


class ManifestLookahead:
    """Keyed single-flight manifest prefetch over a ``SegmentManifestCache``.

    The manifest cache deduplicates *cached* lookups, but a segment-boundary
    crossing still pays the first fetch+parse of the next segment's manifest
    inline on the foreground read. This seam lets whoever can predict the
    crossing (the readahead tier's next-segment resolver, the RSM's fetch
    path) ``prefetch()`` the manifest onto a background worker; ``get()``
    then JOINS the in-flight resolution instead of starting a second one —
    and concurrent prefetches of the same key collapse to one load, keyed
    single-flight, exactly like the chunk cache's per-chunk flights.

    The flight table only holds keys from submit until the load settles
    (the result itself lives in the manifest cache; a failed flight is
    dropped so the next get retries through the cache's own loader).
    """

    def __init__(
        self, cache: SegmentManifestCache, *, max_workers: int = 1
    ) -> None:
        self._cache = cache
        self._executor = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="manifest-lookahead"
        )
        self._lock = new_lock("manifest_cache.ManifestLookahead._lock")
        self._flights: dict[str, "Future[SegmentManifestV1]"] = {}
        # Counters (guarded by _lock; race-checker inventoried).
        self.launches = 0
        self.joins = 0
        self.failures = 0

    def prefetch(
        self, key: ObjectKey, loader: Callable[[], SegmentManifestV1]
    ) -> None:
        """Start resolving ``key``'s manifest in the background (at most one
        flight per key; repeat calls while it resolves are no-ops)."""
        with self._lock:
            if key.value in self._flights:
                return
            future: "Future[SegmentManifestV1]" = Future()
            self._flights[key.value] = future
            self.launches += 1
            note_mutation("manifest_cache.ManifestLookahead.launches")
        self._executor.submit(self._resolve, key, loader, future)

    def _resolve(
        self, key: ObjectKey, loader: Callable[[], SegmentManifestV1],
        future: "Future[SegmentManifestV1]",
    ) -> None:
        try:
            manifest = self._cache.get(key, loader)
        except Exception as e:
            # Drop the failed flight BEFORE resolving it: a get() that
            # arrives later retries through the cache loader instead of
            # inheriting a stale error.
            with self._lock:
                self._flights.pop(key.value, None)
                self.failures += 1
                note_mutation("manifest_cache.ManifestLookahead.failures")
            future.set_exception(e)
            log.debug("Manifest lookahead of %s failed", key.value, exc_info=True)
            return
        with self._lock:
            self._flights.pop(key.value, None)
        future.set_result(manifest)

    def get(
        self, key: ObjectKey, loader: Callable[[], SegmentManifestV1],
        timeout: Optional[float] = None,
    ) -> SegmentManifestV1:
        """The manifest for ``key`` — joining an in-flight prefetch when one
        is resolving, else through the cache (which is where a COMPLETED
        prefetch's result already lives)."""
        with self._lock:
            future = self._flights.get(key.value)
            if future is not None:
                self.joins += 1
                note_mutation("manifest_cache.ManifestLookahead.joins")
        if future is not None:
            try:
                return future.result(timeout=timeout)
            except concurrent.futures.TimeoutError:
                raise TimeoutError(
                    f"Joining manifest lookahead of {key.value} timed out"
                ) from None
            except Exception:
                # The prefetch failed; fall through to an authoritative
                # load of our own (the error, if persistent, surfaces here).
                log.debug(
                    "Joined manifest lookahead of %s failed; retrying "
                    "through the cache loader", key.value, exc_info=True,
                )
        return self._cache.get(key, loader)

    def close(self) -> None:
        self._executor.shutdown(wait=True, cancel_futures=True)
