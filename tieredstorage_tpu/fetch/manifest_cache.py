"""Segment manifest cache: object key -> parsed SegmentManifest.

Reference: core/.../fetch/manifest/SegmentManifestCache.java:26-29 (interface)
and MemorySegmentManifestCache.java (Caffeine AsyncLoadingCache; defaults
1000 entries / 1 h retention :51-52; `get` with timeout :67-89). Sized by
entry count (the manifests are ~KB JSON), unlike the byte-weighed chunk and
index caches.
"""

from __future__ import annotations

import abc
import concurrent.futures
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Mapping, Optional

from tieredstorage_tpu.config.cache_config import CacheConfig
from tieredstorage_tpu.manifest.segment_manifest import SegmentManifestV1
from tieredstorage_tpu.storage.core import ObjectKey
from tieredstorage_tpu.utils.caching import LoadingCache


class SegmentManifestCache(abc.ABC):
    @abc.abstractmethod
    def get(
        self, key: ObjectKey, loader: Callable[[], SegmentManifestV1]
    ) -> SegmentManifestV1:
        """Cached parsed manifest; loads through `loader` at most once."""


class MemorySegmentManifestCache(SegmentManifestCache):
    DEFAULT_MAX_SIZE = 1000
    DEFAULT_RETENTION_MS = 3_600_000  # 1 h

    def __init__(self) -> None:
        self._cache: Optional[LoadingCache[str, SegmentManifestV1]] = None
        self._config: Optional[CacheConfig] = None
        self._executor: Optional[ThreadPoolExecutor] = None

    def configure(self, configs: Mapping[str, Any]) -> None:
        self._config = CacheConfig(
            configs,
            size_default=self.DEFAULT_MAX_SIZE,
            retention_ms_default=self.DEFAULT_RETENTION_MS,
        )
        self._executor = ThreadPoolExecutor(
            max_workers=self._config.thread_pool_size or None,
            thread_name_prefix="manifest-cache",
        )
        self._cache = LoadingCache(
            executor=self._executor,
            max_weight=self._config.cache_size,
            weigher=lambda _m: 1,  # sized by entry count
            expire_after_access_s=self._config.retention_s,
        )

    @property
    def stats(self):
        return self._cache.stats

    @property
    def size(self) -> int:
        return len(self._cache)

    def get(
        self, key: ObjectKey, loader: Callable[[], SegmentManifestV1]
    ) -> SegmentManifestV1:
        try:
            return self._cache.get(key.value, loader, timeout=self._config.get_timeout_s)
        except concurrent.futures.TimeoutError:
            raise TimeoutError(f"Loading manifest {key.value} timed out") from None

    def close(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=True, cancel_futures=True)
