"""Fetch path (reference L3): chunk manager, range enumeration, caches.

Reference: core/src/main/java/io/aiven/kafka/tieredstorage/fetch/.
"""

from tieredstorage_tpu.fetch.chunk_manager import ChunkManager, DefaultChunkManager
from tieredstorage_tpu.fetch.enumeration import FetchChunkEnumeration

__all__ = ["ChunkManager", "DefaultChunkManager", "FetchChunkEnumeration"]
