// Native host transform library: batched zstd + AES-256-GCM.
//
// The reference's performance-critical native code is what its JVM links
// against: zstd-jni for per-chunk compression
// (core/.../transform/CompressionChunkEnumeration.java:50-63) and the JDK's
// AES-GCM intrinsics (EncryptionChunkEnumeration.java:66-81). This library is
// the equivalent native layer for the TPU build's host side: whole chunk
// batches cross the Python boundary once and are compressed/encrypted by a
// C++ thread pool (zstd via libzstd; AES-256-GCM via libcrypto.so.3 resolved
// at runtime with dlopen, since the image ships no OpenSSL headers).
//
// Wire format parity with the reference:
//   compression: one zstd frame per chunk, content size pledged in the frame
//   encryption:  IV(12) || ciphertext || tag(16) per chunk, fresh IV per chunk
//
// C ABI notes: callers pass one contiguous input buffer plus per-chunk sizes,
// and one contiguous output buffer with a fixed per-chunk stride
// (worst-case-bound sized); per-chunk output sizes are returned. No memory
// ownership crosses the boundary.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <dlfcn.h>
#include <zstd.h>

namespace {

// ---------------------------------------------------------------------------
// libcrypto runtime binding (EVP AES-256-GCM)
// ---------------------------------------------------------------------------

typedef struct evp_cipher_ctx_st EVP_CIPHER_CTX;
typedef struct evp_cipher_st EVP_CIPHER;
typedef struct engine_st ENGINE;

struct CryptoApi {
  EVP_CIPHER_CTX *(*ctx_new)();
  void (*ctx_free)(EVP_CIPHER_CTX *);
  int (*ctx_ctrl)(EVP_CIPHER_CTX *, int, int, void *);
  const EVP_CIPHER *(*aes_256_gcm)();
  int (*encrypt_init)(EVP_CIPHER_CTX *, const EVP_CIPHER *, ENGINE *,
                      const unsigned char *, const unsigned char *);
  int (*encrypt_update)(EVP_CIPHER_CTX *, unsigned char *, int *,
                        const unsigned char *, int);
  int (*encrypt_final)(EVP_CIPHER_CTX *, unsigned char *, int *);
  int (*decrypt_init)(EVP_CIPHER_CTX *, const EVP_CIPHER *, ENGINE *,
                      const unsigned char *, const unsigned char *);
  int (*decrypt_update)(EVP_CIPHER_CTX *, unsigned char *, int *,
                        const unsigned char *, int);
  int (*decrypt_final)(EVP_CIPHER_CTX *, unsigned char *, int *);
  bool ok = false;
};

// Stable EVP_CIPHER_CTX_ctrl command values (openssl/evp.h ABI).
constexpr int kGcmSetIvLen = 0x9;
constexpr int kGcmGetTag = 0x10;
constexpr int kGcmSetTag = 0x11;

const CryptoApi &crypto() {
  static CryptoApi api = [] {
    CryptoApi a{};
    void *lib = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (lib == nullptr) lib = dlopen("libcrypto.so.1.1", RTLD_NOW | RTLD_GLOBAL);
    if (lib == nullptr) return a;
    auto sym = [lib](const char *name) { return dlsym(lib, name); };
    a.ctx_new = reinterpret_cast<EVP_CIPHER_CTX *(*)()>(sym("EVP_CIPHER_CTX_new"));
    a.ctx_free = reinterpret_cast<void (*)(EVP_CIPHER_CTX *)>(sym("EVP_CIPHER_CTX_free"));
    a.ctx_ctrl = reinterpret_cast<int (*)(EVP_CIPHER_CTX *, int, int, void *)>(
        sym("EVP_CIPHER_CTX_ctrl"));
    a.aes_256_gcm = reinterpret_cast<const EVP_CIPHER *(*)()>(sym("EVP_aes_256_gcm"));
    a.encrypt_init =
        reinterpret_cast<int (*)(EVP_CIPHER_CTX *, const EVP_CIPHER *, ENGINE *,
                                 const unsigned char *, const unsigned char *)>(
            sym("EVP_EncryptInit_ex"));
    a.encrypt_update = reinterpret_cast<int (*)(EVP_CIPHER_CTX *, unsigned char *, int *,
                                                const unsigned char *, int)>(
        sym("EVP_EncryptUpdate"));
    a.encrypt_final = reinterpret_cast<int (*)(EVP_CIPHER_CTX *, unsigned char *, int *)>(
        sym("EVP_EncryptFinal_ex"));
    a.decrypt_init =
        reinterpret_cast<int (*)(EVP_CIPHER_CTX *, const EVP_CIPHER *, ENGINE *,
                                 const unsigned char *, const unsigned char *)>(
            sym("EVP_DecryptInit_ex"));
    a.decrypt_update = reinterpret_cast<int (*)(EVP_CIPHER_CTX *, unsigned char *, int *,
                                                const unsigned char *, int)>(
        sym("EVP_DecryptUpdate"));
    a.decrypt_final = reinterpret_cast<int (*)(EVP_CIPHER_CTX *, unsigned char *, int *)>(
        sym("EVP_DecryptFinal_ex"));
    a.ok = a.ctx_new && a.ctx_free && a.ctx_ctrl && a.aes_256_gcm && a.encrypt_init &&
           a.encrypt_update && a.encrypt_final && a.decrypt_init && a.decrypt_update &&
           a.decrypt_final;
    return a;
  }();
  return api;
}

// ---------------------------------------------------------------------------
// Thread pool helper: run fn(chunk_index) over [0, n) on up to n_threads.
// ---------------------------------------------------------------------------

template <typename Fn>
void parallel_for(int n, int n_threads, Fn fn) {
  if (n <= 0) return;
  int workers = n_threads > 0 ? n_threads : static_cast<int>(std::thread::hardware_concurrency());
  if (workers > n) workers = n;
  if (workers <= 1) {
    for (int i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int> next{0};
  std::vector<std::thread> threads;
  threads.reserve(workers);
  for (int t = 0; t < workers; ++t) {
    threads.emplace_back([&] {
      for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) fn(i);
    });
  }
  for (auto &th : threads) th.join();
}

constexpr size_t kIvSize = 12;
constexpr size_t kTagSize = 16;
// EVP_*Update takes int lengths; larger chunks must be rejected, not wrapped.
constexpr uint64_t kMaxAesChunk = 0x7FFFFFFF;

}  // namespace

extern "C" {

// Returns 1 when the AES path is usable (libcrypto resolved).
int ts_crypto_available() { return crypto().ok ? 1 : 0; }

// Worst-case compressed size for a chunk of `size` bytes.
size_t ts_zstd_bound(size_t size) { return ZSTD_compressBound(size); }

// Compress n chunks. Inputs are consecutive in `in` at `in_offsets[i]` with
// `in_sizes[i]`; chunk i's frame is written at out + i*out_stride, its size
// into out_sizes[i]. Returns 0 on success, or 1+index of the failing chunk.
int ts_zstd_compress_batch(const uint8_t *in, const uint64_t *in_offsets,
                           const uint64_t *in_sizes, int n, int level,
                           uint8_t *out, uint64_t out_stride,
                           uint64_t *out_sizes, int n_threads) {
  std::atomic<int> err{0};
  parallel_for(n, n_threads, [&](int i) {
    if (err.load(std::memory_order_relaxed) != 0) return;
    // A context per task keeps frames identical to one-shot compression
    // (content size pledged in the frame header, like the reference's
    // setPledgedSrcSize + setContentSize(true)).
    size_t written = ZSTD_compress(out + static_cast<size_t>(i) * out_stride, out_stride,
                                   in + in_offsets[i], in_sizes[i], level);
    if (ZSTD_isError(written)) {
      int expected = 0;
      err.compare_exchange_strong(expected, 1 + i);
      return;
    }
    out_sizes[i] = written;
  });
  return err.load();
}

// Decompress n zstd frames (content size must be in the frame header).
int ts_zstd_decompress_batch(const uint8_t *in, const uint64_t *in_offsets,
                             const uint64_t *in_sizes, int n, uint8_t *out,
                             uint64_t out_stride, uint64_t *out_sizes,
                             int n_threads) {
  std::atomic<int> err{0};
  parallel_for(n, n_threads, [&](int i) {
    if (err.load(std::memory_order_relaxed) != 0) return;
    const uint8_t *src = in + in_offsets[i];
    unsigned long long content = ZSTD_getFrameContentSize(src, in_sizes[i]);
    if (content == ZSTD_CONTENTSIZE_ERROR || content == ZSTD_CONTENTSIZE_UNKNOWN ||
        content > out_stride) {
      int expected = 0;
      err.compare_exchange_strong(expected, 1 + i);
      return;
    }
    size_t written = ZSTD_decompress(out + static_cast<size_t>(i) * out_stride, out_stride,
                                     src, in_sizes[i]);
    if (ZSTD_isError(written) || written != content) {
      int expected = 0;
      err.compare_exchange_strong(expected, 1 + i);
      return;
    }
    out_sizes[i] = written;
  });
  return err.load();
}

// AES-256-GCM encrypt n chunks: out[i] = IV || ciphertext || tag at
// out + i*out_stride (out_stride >= in_sizes[i] + 28). IVs are caller-supplied
// (n * 12 bytes) so the Python layer controls IV uniqueness policy.
// Returns 0 on success, 1+i for a cipher failure on chunk i, -(2+i) when
// chunk i (or the AAD) exceeds the int length limit, -1 if libcrypto is
// unavailable.
int ts_aes_gcm_encrypt_batch(const uint8_t *key, const uint8_t *aad, uint64_t aad_len,
                             const uint8_t *ivs, const uint8_t *in,
                             const uint64_t *in_offsets, const uint64_t *in_sizes,
                             int n, uint8_t *out, uint64_t out_stride,
                             uint64_t *out_sizes, int n_threads) {
  const CryptoApi &api = crypto();
  if (!api.ok) return -1;
  std::atomic<int> err{0};
  parallel_for(n, n_threads, [&](int i) {
    if (err.load(std::memory_order_relaxed) != 0) return;
    if (in_sizes[i] > kMaxAesChunk || aad_len > kMaxAesChunk) {
      int expected = 0;
      err.compare_exchange_strong(expected, -(2 + i));
      return;
    }
    uint8_t *dst = out + static_cast<size_t>(i) * out_stride;
    const uint8_t *iv = ivs + static_cast<size_t>(i) * kIvSize;
    EVP_CIPHER_CTX *ctx = api.ctx_new();
    bool fail = ctx == nullptr;
    int len = 0;
    if (!fail) fail = api.encrypt_init(ctx, api.aes_256_gcm(), nullptr, nullptr, nullptr) != 1;
    if (!fail) fail = api.ctx_ctrl(ctx, kGcmSetIvLen, kIvSize, nullptr) != 1;
    if (!fail) fail = api.encrypt_init(ctx, nullptr, nullptr, key, iv) != 1;
    if (!fail && aad_len > 0)
      fail = api.encrypt_update(ctx, nullptr, &len, aad, static_cast<int>(aad_len)) != 1;
    std::memcpy(dst, iv, kIvSize);
    if (!fail)
      fail = api.encrypt_update(ctx, dst + kIvSize, &len, in + in_offsets[i],
                                static_cast<int>(in_sizes[i])) != 1;
    int ct_len = len;
    if (!fail) fail = api.encrypt_final(ctx, dst + kIvSize + ct_len, &len) != 1;
    ct_len += len;
    if (!fail)
      fail = api.ctx_ctrl(ctx, kGcmGetTag, kTagSize, dst + kIvSize + ct_len) != 1;
    if (ctx != nullptr) api.ctx_free(ctx);
    if (fail) {
      int expected = 0;
      err.compare_exchange_strong(expected, 1 + i);
      return;
    }
    out_sizes[i] = kIvSize + ct_len + kTagSize;
  });
  return err.load();
}

// AES-256-GCM decrypt n chunks of IV || ciphertext || tag. Returns 0 on
// success, 1+index of the first failing chunk (bad tag included), -(2+i)
// when chunk i (or the AAD) exceeds the int length limit, -1 when libcrypto
// is unavailable.
int ts_aes_gcm_decrypt_batch(const uint8_t *key, const uint8_t *aad, uint64_t aad_len,
                             const uint8_t *in, const uint64_t *in_offsets,
                             const uint64_t *in_sizes, int n, uint8_t *out,
                             uint64_t out_stride, uint64_t *out_sizes, int n_threads) {
  const CryptoApi &api = crypto();
  if (!api.ok) return -1;
  std::atomic<int> err{0};
  parallel_for(n, n_threads, [&](int i) {
    if (err.load(std::memory_order_relaxed) != 0) return;
    const uint8_t *src = in + in_offsets[i];
    if (in_sizes[i] > kMaxAesChunk || aad_len > kMaxAesChunk) {
      // Size-limit rejection, NOT an auth failure: distinct code -(2+i).
      int expected = 0;
      err.compare_exchange_strong(expected, -(2 + i));
      return;
    }
    if (in_sizes[i] < kIvSize + kTagSize) {
      int expected = 0;
      err.compare_exchange_strong(expected, 1 + i);
      return;
    }
    const uint8_t *iv = src;
    const uint8_t *ct = src + kIvSize;
    size_t ct_len = in_sizes[i] - kIvSize - kTagSize;
    uint8_t tag[kTagSize];
    std::memcpy(tag, src + in_sizes[i] - kTagSize, kTagSize);
    uint8_t *dst = out + static_cast<size_t>(i) * out_stride;
    EVP_CIPHER_CTX *ctx = api.ctx_new();
    bool fail = ctx == nullptr;
    int len = 0;
    if (!fail) fail = api.decrypt_init(ctx, api.aes_256_gcm(), nullptr, nullptr, nullptr) != 1;
    if (!fail) fail = api.ctx_ctrl(ctx, kGcmSetIvLen, kIvSize, nullptr) != 1;
    if (!fail) fail = api.decrypt_init(ctx, nullptr, nullptr, key, iv) != 1;
    if (!fail && aad_len > 0)
      fail = api.decrypt_update(ctx, nullptr, &len, aad, static_cast<int>(aad_len)) != 1;
    if (!fail)
      fail = api.decrypt_update(ctx, dst, &len, ct, static_cast<int>(ct_len)) != 1;
    int pt_len = len;
    if (!fail) fail = api.ctx_ctrl(ctx, kGcmSetTag, kTagSize, tag) != 1;
    if (!fail) fail = api.decrypt_final(ctx, dst + pt_len, &len) != 1;  // tag check
    pt_len += len;
    if (ctx != nullptr) api.ctx_free(ctx);
    if (fail) {
      int expected = 0;
      err.compare_exchange_strong(expected, 1 + i);
      return;
    }
    out_sizes[i] = pt_len;
  });
  return err.load();
}

// Expand one tpu-lzhuff-v1 sequence stream (transform/lzhuff.py): n_seq
// records of <lit_len u16, match_len u16, offset u16>, literals consumed
// from `lits`. Offset 0 on a match repeats the previous match's offset
// (the rep-offset sentinel); offsets may be smaller than the match length
// (overlapped copy — how runs encode). Returns 0 on success; 1 = literal
// overflow, 2 = match outside the decoded prefix, 3 = totals mismatch.
// The role the reference's zstd-jni native decode path plays, for this
// build's codec.
int ts_lz_expand(const uint16_t* seqs, int n_seq,
                 const uint8_t* lits, uint64_t lit_total,
                 uint8_t* out, uint64_t out_len) {
  // The Python caller serializes sequences as numpy '<u2' (explicit
  // little-endian); decode byte-wise so this expander and the numpy
  // fallback agree on any host endianness.
  const uint8_t* sb = reinterpret_cast<const uint8_t*>(seqs);
  const auto u16le = [sb](uint64_t idx) -> uint64_t {
    return static_cast<uint64_t>(sb[2 * idx]) |
           (static_cast<uint64_t>(sb[2 * idx + 1]) << 8);
  };
  uint64_t o = 0, lp = 0, last_d = 0;
  for (int i = 0; i < n_seq; ++i) {
    const uint64_t base = 3ull * static_cast<uint64_t>(i);
    const uint64_t lit = u16le(base);
    const uint64_t m = u16le(base + 1);
    uint64_t d = u16le(base + 2);
    if (lit) {
      if (lp + lit > lit_total || o + lit > out_len) return 1;
      std::memcpy(out + o, lits + lp, lit);
      o += lit;
      lp += lit;
    }
    if (m) {
      if (d == 0) d = last_d;  // repeat-offset sentinel
      last_d = d;
      if (d < 1 || d > o || o + m > out_len) return 2;
      if (d >= m) {
        std::memcpy(out + o, out + o - d, m);
      } else {
        uint8_t* dst = out + o;
        const uint8_t* src = out + o - d;
        for (uint64_t j = 0; j < m; ++j) dst[j] = src[j];
      }
      o += m;
    }
  }
  if (o != out_len || lp != lit_total) return 3;
  return 0;
}

}  // extern "C"
